"""Request-resilience primitives: retries, deadlines, breakers, admission.

Reference semantics: SURVEY §5 "Failure detection / elastic".  The lease
plane (transports/hub.py) detects a dead worker only after its TTL expires;
between the crash and the expiry every routed request would land on a corpse.
This module closes that window at the request level:

- ``RetryPolicy``     — bounded attempts, exponential backoff with FULL
  jitter (the AWS-architecture-blog shape: ``sleep = rand(0, min(cap,
  base * 2**attempt))``), so a thundering herd of failing clients decorrelates
  instead of synchronizing on the backoff ladder.
- ``Deadline``        — a wall-clock budget carried on the request context and
  decremented across hops (client pick → connect → first token → disagg
  transfer wait); the HTTP edge maps exhaustion to 504.
- ``CircuitBreaker``  — per-worker-address connect/prologue health: CLOSED →
  OPEN after N consecutive failures, then a single HALF_OPEN probe after the
  reset window; success closes, failure re-opens.  Routing skips OPEN workers
  so a corpse stops eating retry budget after the first few requests.
- ``AdmissionController`` — HTTP-edge load shedding: an in-flight cap plus a
  bounded FIFO wait queue.  Queue overflow sheds immediately with 429; a
  queued request that cannot get a slot within the wait budget sheds with
  503.  Both carry ``Retry-After`` (lib/llm http service returns 429 on
  model-busy; the cap here is service-wide).
- ``ResilienceMetrics`` — process-global counters + breaker-state gauges
  rendered as Prometheus text and appended to the existing ``/metrics``
  exposition (llm/http_service.py), so breaker opens and shed counts are
  observable without a new scrape target.

Everything here is pure host-side asyncio/stdlib — no JAX, no new deps.
"""

from __future__ import annotations

import asyncio
import enum
import random
import time
from collections import deque
from dataclasses import dataclass

from ..labels import escape_label
from typing import Any, Callable, Dict, Mapping, Optional, Tuple


# --------------------------------------------------------------------------
# Deadlines
# --------------------------------------------------------------------------


class DeadlineExceededError(TimeoutError):
    """The request's deadline budget is exhausted (HTTP edge → 504)."""


class Deadline:
    """A monotonic-clock budget threaded through Context across hops."""

    __slots__ = ("expires_at",)

    def __init__(self, expires_at: float):
        self.expires_at = expires_at

    @classmethod
    def after(cls, seconds: float) -> "Deadline":
        return cls(time.monotonic() + seconds)

    def remaining(self) -> float:
        return self.expires_at - time.monotonic()

    @property
    def expired(self) -> bool:
        return self.remaining() <= 0

    def check(self, what: str = "request") -> None:
        if self.expired:
            raise DeadlineExceededError(f"deadline exceeded ({what})")

    async def bound(self, awaitable, what: str = "request"):
        """Await with the remaining budget; timeout → DeadlineExceededError."""
        try:
            return await asyncio.wait_for(awaitable, max(self.remaining(), 0.0))
        except asyncio.TimeoutError:
            raise DeadlineExceededError(f"deadline exceeded ({what})") from None


def deadline_of(ctx) -> Optional[Deadline]:
    """The Deadline attached to an AsyncEngineContext (or None)."""
    return getattr(ctx, "deadline", None)


# --------------------------------------------------------------------------
# Retry policy
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class RetryPolicy:
    """Bounded retries with exponential backoff and full jitter."""

    max_attempts: int = 3
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    # Injectable jitter source: seeded chaos-ladder / sim runs pass a
    # random.Random(seed) so backoff schedules replay exactly; production
    # keeps full-jitter from the process RNG.
    rng: Optional[random.Random] = None

    def backoff(self, attempt: int) -> float:
        """Sleep before retry ``attempt`` (1-based): rand(0, min(cap, base·2ⁿ))."""
        cap = min(self.max_delay_s, self.base_delay_s * (2 ** max(attempt - 1, 0)))
        return (self.rng or random).uniform(0.0, cap)

    @classmethod
    def from_config(cls, cfg: Optional[Mapping[str, Any]]) -> "RetryPolicy":
        cfg = cfg or {}
        return cls(
            max_attempts=int(cfg.get("retry_max_attempts", cls.max_attempts)),
            base_delay_s=float(cfg.get("retry_base_delay_s", cls.base_delay_s)),
            max_delay_s=float(cfg.get("retry_max_delay_s", cls.max_delay_s)),
        )


# --------------------------------------------------------------------------
# Circuit breaker
# --------------------------------------------------------------------------


class BreakerState(enum.Enum):
    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"


class CircuitBreaker:
    """Per-worker connect/stream-prologue health with a half-open probe.

    Only CONNECT-time and prologue failures trip the breaker — an engine
    raising on a malformed request is the request's fault, not the worker's.
    """

    def __init__(
        self,
        key: str = "",
        failure_threshold: int = 3,
        reset_timeout_s: float = 5.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.key = key
        self.failure_threshold = max(1, failure_threshold)
        self.reset_timeout_s = reset_timeout_s
        self._clock = clock
        self._state = BreakerState.CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0

    @property
    def state(self) -> BreakerState:
        return self._state

    def can_attempt(self) -> bool:
        """Pure check: may this worker receive a request right now?"""
        if self._state is BreakerState.CLOSED:
            return True
        if self._state is BreakerState.OPEN:
            return (self._clock() - self._opened_at) >= self.reset_timeout_s
        return False  # HALF_OPEN: one probe already in flight

    def on_attempt(self) -> None:
        """Mark a request dispatched; OPEN past the reset window → HALF_OPEN
        (this attempt IS the probe; concurrent picks skip the worker)."""
        if self._state is BreakerState.OPEN and self.can_attempt():
            self._transition(BreakerState.HALF_OPEN)

    def record_success(self) -> None:
        self._consecutive_failures = 0
        if self._state is not BreakerState.CLOSED:
            self._transition(BreakerState.CLOSED)

    def release_probe(self) -> None:
        """The in-flight half-open probe ended inconclusively (deadline hit,
        caller cancelled, non-retryable request error — none of which prove
        the WORKER sick or healthy): return to OPEN keeping the original
        open timestamp, so the next pick may probe immediately.  Without
        this the breaker wedges in HALF_OPEN (can_attempt always False) and
        a recovered worker is excluded from routing forever."""
        if self._state is BreakerState.HALF_OPEN:
            self._transition(BreakerState.OPEN)

    def record_failure(self) -> None:
        self._consecutive_failures += 1
        if self._state is BreakerState.HALF_OPEN:
            self._opened_at = self._clock()
            self._transition(BreakerState.OPEN)
        elif (
            self._state is BreakerState.CLOSED
            and self._consecutive_failures >= self.failure_threshold
        ):
            self._opened_at = self._clock()
            self._transition(BreakerState.OPEN)

    def _transition(self, state: BreakerState) -> None:
        self._state = state
        metrics.breaker_transitions[(self.key, state.value)] = (
            metrics.breaker_transitions.get((self.key, state.value), 0) + 1
        )


# --------------------------------------------------------------------------
# HTTP admission control
# --------------------------------------------------------------------------


class AdmissionRejected(Exception):
    """Load shed at the HTTP edge (429 queue-full / 503 wait-timeout)."""

    def __init__(self, status: int, message: str, retry_after_s: float):
        super().__init__(message)
        self.status = status
        self.message = message
        self.retry_after_s = retry_after_s


class AdmissionController:
    """In-flight cap + bounded FIFO wait queue with a wait budget.

    ``max_inflight=None`` disables admission control entirely (the default:
    zero behaviour change for embedded/test services).

    QoS extensions (llm/qos.py):

    - ``acquire(priority)`` — ``batch``-class requests may only occupy the
      FRONT fraction of the wait queue (``batch_queue_frac``); the rest is
      reserved headroom for interactive arrivals, so a batch burst cannot
      queue interactive traffic out under pressure.
    - ``estimate_retry_after`` — Retry-After computed from the measured
      queue DRAIN RATE (recent slot releases per second) instead of a fixed
      constant, so shed clients back off proportionally to real pressure.
    """

    # Releases sampled for the drain-rate estimate (~the last few seconds
    # of churn at any realistic service rate).
    DRAIN_WINDOW = 64

    def __init__(
        self,
        max_inflight: Optional[int] = None,
        max_queue: int = 0,
        queue_timeout_s: float = 1.0,
        batch_queue_frac: float = 0.5,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.max_inflight = max_inflight
        self.max_queue = max(0, max_queue)
        self.queue_timeout_s = queue_timeout_s
        self.batch_queue_frac = min(max(batch_queue_frac, 0.0), 1.0)
        self._clock = clock
        self._inflight = 0
        self._waiters: deque = deque()  # FIFO of futures awaiting a slot
        self._releases: deque = deque(maxlen=self.DRAIN_WINDOW)

    @property
    def inflight(self) -> int:
        return self._inflight

    @property
    def queued(self) -> int:
        return len(self._waiters)

    @property
    def saturated(self) -> bool:
        """Admission would queue (or shed) right now — the brownout
        ladder's rung-4 'interactive overflow' predicate."""
        return self.max_inflight is not None and self._inflight >= self.max_inflight

    def drain_rate(self) -> float:
        """Recent slot releases per second (0.0 until enough samples)."""
        if len(self._releases) < 2:
            return 0.0
        span = self._releases[-1] - self._releases[0]
        if span <= 0:
            return 0.0
        return (len(self._releases) - 1) / span

    def estimate_retry_after(self, ahead: Optional[int] = None) -> float:
        """Seconds until roughly ``ahead`` queued requests drain (default:
        the current queue plus one — where a new arrival would land).
        Falls back to the wait budget before any drain history exists."""
        ahead = len(self._waiters) + 1 if ahead is None else max(ahead, 1)
        rate = self.drain_rate()
        if rate <= 0:
            return max(1.0, self.queue_timeout_s)
        return min(max(ahead / rate, 0.05), 60.0)

    def _retry_after(self) -> float:
        return self.estimate_retry_after()

    async def acquire(self, priority: str = "interactive") -> None:
        if self.max_inflight is None:
            return
        if self._inflight < self.max_inflight:
            self._inflight += 1
            return
        # Queue reservation: batch requests only occupy the front
        # batch_queue_frac of the wait queue; the remainder stays free for
        # interactive arrivals (protected admission under pressure).
        limit = (
            int(self.max_queue * self.batch_queue_frac)
            if priority == "batch"
            else self.max_queue
        )
        if len(self._waiters) >= limit:
            metrics.admission_shed["429"] = metrics.admission_shed.get("429", 0) + 1
            raise AdmissionRejected(
                429, "server overloaded (admission queue full)", self._retry_after()
            )
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._waiters.append(fut)
        try:
            await asyncio.wait_for(fut, self.queue_timeout_s)
        except asyncio.TimeoutError:
            if fut.done() and not fut.cancelled():
                # release() handed the slot over in the same tick the timer
                # fired — keep it, or the transferred slot leaks forever.
                return
            self._discard(fut)
            metrics.admission_shed["503"] = metrics.admission_shed.get("503", 0) + 1
            raise AdmissionRejected(
                503, "server overloaded (admission wait timed out)", self._retry_after()
            ) from None
        except asyncio.CancelledError:
            if fut.done() and not fut.cancelled():
                self.release()  # slot was handed over as we were cancelled
            else:
                self._discard(fut)
            raise
        # fut resolved: the releasing request handed its slot to us
        # (inflight count was transferred, not decremented).

    def release(self) -> None:
        if self.max_inflight is None:
            return
        self._releases.append(self._clock())
        while self._waiters:
            fut = self._waiters.popleft()
            if not fut.done():
                fut.set_result(None)  # hand the slot over; _inflight unchanged
                return
        self._inflight = max(0, self._inflight - 1)

    def _discard(self, fut: asyncio.Future) -> None:
        try:
            self._waiters.remove(fut)
        except ValueError:
            pass

    @classmethod
    def from_config(cls, cfg: Optional[Mapping[str, Any]]) -> "AdmissionController":
        cfg = cfg or {}
        raw = cfg.get("http_max_inflight")
        return cls(
            max_inflight=int(raw) if raw not in (None, "", 0) else None,
            max_queue=int(cfg.get("http_admission_queue", 0)),
            queue_timeout_s=float(cfg.get("http_admission_timeout_s", 1.0)),
            batch_queue_frac=float(cfg.get("http_batch_queue_frac", 0.5)),
        )


# --------------------------------------------------------------------------
# Metrics (appended to the existing Prometheus exposition)
# --------------------------------------------------------------------------


class ResilienceMetrics:
    """Process-global resilience counters + breaker gauges.

    Rendered as Prometheus text by ``render()`` and appended to the HTTP
    service's ``/metrics`` body — plain ints, no prometheus_client registry,
    so the runtime layer stays dependency-free.
    """

    def __init__(self):
        self.retries_total = 0
        self.failovers_total = 0
        self.retries_exhausted_total = 0
        self.deadline_exceeded_total = 0
        self.watch_restarts_total = 0
        self.degraded_prefills_total = 0
        # Live-migration stream splices (client consumed a ``migrated``
        # marker and re-dispatched to the target worker).
        self.migration_splices_total = 0
        # Mid-stream crash recoveries: a seeded request's stream was
        # reconstructed from delivered tokens and resumed elsewhere.
        self.stream_resumes_total = 0
        # Hub session resume (transports/hub.py HubClient): reconnects to a
        # restarted/recovered hub, subscriptions re-armed onto their live
        # consumers, and unacked queue items returned to the queue.
        self.hub_reconnects_total = 0
        self.hub_sessions_resumed_total = 0
        self.hub_requeued_items_total = 0
        self.admission_shed: Dict[str, int] = {}
        self.breaker_transitions: Dict[Tuple[str, str], int] = {}
        self._breakers: Dict[str, CircuitBreaker] = {}

    def register_breaker(self, breaker: CircuitBreaker) -> CircuitBreaker:
        self._breakers[breaker.key] = breaker
        return breaker

    def unregister_breaker(self, key: str) -> None:
        """Drop a departed worker's gauge (clients prune on instance removal
        so restart-churned ephemeral addresses don't accumulate forever)."""
        self._breakers.pop(key, None)

    def breaker_states(self) -> Dict[str, str]:
        return {k: b.state.value for k, b in self._breakers.items()}

    def reset(self) -> None:
        self.__init__()

    def render(self, prefix: str = "dynamo_tpu") -> str:
        ns = f"{prefix}_resilience"
        lines = []

        def counter(name: str, help_: str, value: int) -> None:
            lines.append(f"# HELP {ns}_{name} {help_}")
            lines.append(f"# TYPE {ns}_{name} counter")
            lines.append(f"{ns}_{name} {value}")

        counter("retries_total", "Connect/prologue retries", self.retries_total)
        counter("failovers_total", "Requests failed over to another worker",
                self.failovers_total)
        counter("retries_exhausted_total",
                "Requests that exhausted their retry budget",
                self.retries_exhausted_total)
        counter("deadline_exceeded_total", "Requests past their deadline",
                self.deadline_exceeded_total)
        counter("watch_restarts_total", "Instance-watch loops re-established",
                self.watch_restarts_total)
        counter("degraded_prefills_total",
                "Disagg remote prefills degraded to local",
                self.degraded_prefills_total)
        counter("migration_splices_total",
                "Streams spliced to a migration target mid-flight",
                self.migration_splices_total)
        counter("stream_resumes_total",
                "Seeded streams resumed on another worker after a "
                "mid-stream crash",
                self.stream_resumes_total)
        counter("hub_reconnects_total",
                "Hub connections re-established after loss",
                self.hub_reconnects_total)
        counter("hub_sessions_resumed_total",
                "Hub subscriptions re-armed across a reconnect",
                self.hub_sessions_resumed_total)
        counter("hub_requeued_items_total",
                "Unacked queue items returned to the hub queue on "
                "connection loss",
                self.hub_requeued_items_total)
        lines.append(f"# HELP {ns}_admission_shed_total Requests shed at admission")
        lines.append(f"# TYPE {ns}_admission_shed_total counter")
        for code, n in sorted(self.admission_shed.items()):
            lines.append(f'{ns}_admission_shed_total{{status="{escape_label(code)}"}} {n}')
        # Breaker state gauge: 0=closed 1=half_open 2=open
        state_code = {"closed": 0, "half_open": 1, "open": 2}
        lines.append(f"# HELP {ns}_breaker_state Circuit state (0=closed 1=half-open 2=open)")
        lines.append(f"# TYPE {ns}_breaker_state gauge")
        for key, b in sorted(self._breakers.items()):
            lines.append(
                f'{ns}_breaker_state{{worker="{escape_label(key)}"}} '
                f"{state_code[b.state.value]}"
            )
        lines.append(f"# HELP {ns}_breaker_transitions_total Breaker state transitions")
        lines.append(f"# TYPE {ns}_breaker_transitions_total counter")
        for (key, state), n in sorted(self.breaker_transitions.items()):
            lines.append(
                f'{ns}_breaker_transitions_total{{worker="{escape_label(key)}",'
                f'to="{escape_label(state)}"}} {n}'
            )
        return "\n".join(lines) + "\n"


metrics = ResilienceMetrics()
