"""Distributed request tracing: the span plane (ISSUE 15).

One request crossing the fleet — HTTP edge → preprocessor → routed client →
worker engine → disagg prefill worker → cross-worker KV donor → migration
target — leaves a timeline nobody can currently reconstruct: /metrics
aggregates per component, and the engine's step_trace never leaves its
process.  This module is the process-local half of the tracing plane:

- ``TraceContext`` — the wire identity (trace_id / span_id / sampled) that
  rides every existing hop using the established omit-when-absent idiom:
  ``annotations.trace`` on PreprocessedRequest dicts, a ``trace`` key in the
  service-transport request header, disagg queue items, ``kv_export`` pull
  requests, migration blocks/commit payloads, and the migration snapshot —
  so a spliced, failed-over or migrated stream stays ONE trace.
- ``SpanCollector`` — a bounded process-local ring of finished spans.
  Monotonic clocks (``time.perf_counter``) with one wall anchor per process
  make same-host spans orderable across processes without a clock protocol.
- ``SpanExporter`` — drains the ring on an interval and publishes batches on
  the hub event plane (subject ``{namespace}.traces``), where an edge-side
  ``TraceAggregator`` (llm/trace_service.py) assembles them by trace_id.
- ``TraceSampler`` — head sampling (``tracing.sample`` config rate), forced
  sampling (``x-trace`` header / ``nvext.trace``), and edge-side tail-keep
  for error / SLO-violating requests.

Overhead contract (gated by tests/test_tracing.py): tracing on vs off is
byte-identical streams with zero new XLA compiles.  Every instrumentation
point is behind an ``is None`` check on the context; an unsampled request
allocates nothing.  Decode records at CHUNK granularity only (one span per
fused dispatch per traced row), never per token.

Config (``tracing`` section of RuntimeConfig; env ``DYN_TRACING__*``):
``enabled`` (default True), ``sample`` (head rate, default 0.0 — only
forced traces), ``ring`` (span ring size), ``export_interval_s``,
``ttl_s`` (aggregator assembly TTL), ``tail_keep`` (default True),
``tail_slo_ttft_ms`` (TTFT above this tail-keeps the edge spans).
"""

from __future__ import annotations

import asyncio
import logging
import os
import random
import time
import uuid
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

logger = logging.getLogger(__name__)

# Event-plane topic the exporters publish span batches on (namespace-scoped
# by Namespace.publish, like the planner's slo_metrics subject).
TRACES_TOPIC = "traces"

# One wall anchor per process: span timestamps ship as anchored wall ms so
# the aggregator can order spans from different processes on one host
# without a clock-sync protocol (perf_counter epochs differ per process).
_WALL_ANCHOR = time.time() - time.perf_counter()


def _wall_ms(perf_t: float) -> float:
    return (perf_t + _WALL_ANCHOR) * 1e3


def new_id() -> str:
    """128-bit random id, hex — no coordination needed between processes."""
    return uuid.uuid4().hex


@dataclass
class TraceContext:
    """The per-request trace identity that crosses process boundaries.

    ``span_id`` names the span all spans recorded UNDER this context parent
    to (the edge's root span records with this id itself).  The wire form is
    a plain dict; ``sampled`` ships omit-when-absent (only when False) so
    pre-tracing consumers — and the common sampled case — see the minimal
    shape.
    """

    trace_id: str
    span_id: str
    sampled: bool = True

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
        }
        if not self.sampled:
            # Omitted when absent (= default True): the common sampled
            # context keeps the minimal wire shape, and consumers that
            # predate the field never see it.
            out["sampled"] = self.sampled
        return out

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TraceContext":
        return cls(
            trace_id=str(d["trace_id"]),
            span_id=str(d["span_id"]),
            sampled=bool(d.get("sampled", True)),
        )

    @classmethod
    def new(cls, sampled: bool = True) -> "TraceContext":
        return cls(trace_id=new_id(), span_id=new_id(), sampled=sampled)


def parse_trace(raw: Any) -> Optional[TraceContext]:
    """Tolerant wire parse: annotations/headers come off the wire, so a
    malformed trace dict must degrade to 'untraced', never raise into the
    request path."""
    if not isinstance(raw, dict):
        return None
    try:
        tc = TraceContext.from_dict(raw)
    except (KeyError, TypeError, ValueError):
        return None
    return tc if tc.sampled else None


class TracingMetrics:
    """``dynamo_tpu_tracing_*`` counters.  Module-level singleton rendered
    as Prometheus text and appended to ``/metrics`` (same pattern as
    ``spec_metrics``); the aggregator registers a source callable for its
    assembly gauges the way ``engine_dispatch_metrics`` does."""

    def __init__(self):
        self.spans_recorded_total = 0
        self.spans_dropped_total = 0      # ring overflow (oldest evicted)
        self.traces_sampled_total = 0     # head-sampled at the edge
        self.traces_forced_total = 0      # x-trace / nvext.trace
        self.tail_kept_total = 0          # error/SLO tail-keep promotions
        self.export_batches_total = 0
        self.export_errors_total = 0
        self._aggregator_source: Optional[Callable[[], Dict[str, Any]]] = None

    def set_aggregator_source(self, source) -> None:
        """``source() -> {"traces": n, "orphan_spans": n, "evicted": n}``
        (TraceAggregator.stats), or None to detach."""
        self._aggregator_source = source

    def reset(self) -> None:
        self.__init__()

    def snapshot(self) -> Dict[str, float]:
        return {
            k: float(v)
            for k, v in vars(self).items()
            if isinstance(v, (int, float))
        }

    def render(self, prefix: str = "dynamo_tpu") -> str:
        ns = f"{prefix}_tracing"
        lines: List[str] = []

        def emit(name: str, kind: str, help_: str, value) -> None:
            lines.append(f"# HELP {ns}_{name} {help_}")
            lines.append(f"# TYPE {ns}_{name} {kind}")
            lines.append(f"{ns}_{name} {value}")

        emit("spans_recorded_total", "counter",
             "Spans recorded into the process-local ring",
             self.spans_recorded_total)
        emit("spans_dropped_total", "counter",
             "Spans evicted unexported (ring overflow)",
             self.spans_dropped_total)
        emit("traces_sampled_total", "counter",
             "Traces head-sampled at the edge", self.traces_sampled_total)
        emit("traces_forced_total", "counter",
             "Traces forced via x-trace / nvext.trace",
             self.traces_forced_total)
        emit("tail_kept_total", "counter",
             "Edge traces kept by the error/SLO tail-keep path",
             self.tail_kept_total)
        emit("export_batches_total", "counter",
             "Span batches published on the traces subject",
             self.export_batches_total)
        emit("export_errors_total", "counter",
             "Span batch publishes that failed", self.export_errors_total)
        if self._aggregator_source is not None:
            try:
                s = self._aggregator_source()
            except Exception:  # noqa: BLE001 — aggregator mid-teardown
                s = {}
            emit("aggregator_traces", "gauge",
                 "Traces currently assembled (within TTL)",
                 s.get("traces", 0))
            emit("aggregator_orphan_spans_total", "counter",
                 "Spans whose trace expired without a root span",
                 s.get("orphan_spans", 0))
            emit("aggregator_evicted_total", "counter",
                 "Assembled traces evicted by TTL/capacity",
                 s.get("evicted", 0))
        return "\n".join(lines) + "\n"


tracing_metrics = TracingMetrics()


class SpanCollector:
    """Bounded process-local ring of finished spans.

    ``record`` is called from request hot paths, so it is plain list/dict
    work — no awaits, no locks (asyncio single-thread), no device access.
    An exporter drains the ring; without one the deque bound caps memory
    and the overflow counter records what was lost.
    """

    def __init__(self, maxlen: int = 8192):
        self._ring: deque = deque(maxlen=maxlen)
        # Process label: distinguishes same-host processes in assembled
        # traces (goodput/test fleets also set per-worker labels).
        self.proc = f"pid-{os.getpid()}"

    def __len__(self) -> int:
        return len(self._ring)

    def set_capacity(self, maxlen: int) -> None:
        self._ring = deque(self._ring, maxlen=max(1, int(maxlen)))

    def record(
        self,
        tc: TraceContext,
        name: str,
        component: str,
        start: float,
        end: float,
        attrs: Optional[Dict[str, Any]] = None,
        events: Optional[List[Dict[str, Any]]] = None,
        span_id: Optional[str] = None,
        parent_id: Optional[str] = "",
    ) -> Optional[Dict[str, Any]]:
        """Record one finished span under ``tc``.  ``start``/``end`` are
        ``time.perf_counter`` values; the ring stores anchored wall ms.
        ``parent_id``: default ("") parents to the context's span; None
        marks a ROOT span (and the span takes the context's span_id unless
        an explicit one is given)."""
        if tc is None or not tc.sampled:
            return None
        if parent_id == "":
            parent_id = tc.span_id
        span = {
            "trace_id": tc.trace_id,
            "span_id": span_id
            or (tc.span_id if parent_id is None else new_id()),
            "parent_id": parent_id,
            "name": name,
            "component": component,
            "proc": self.proc,
            "start_ms": round(_wall_ms(start), 3),
            "dur_ms": round(max(end - start, 0.0) * 1e3, 3),
        }
        if attrs:
            span["attrs"] = attrs
        if events:
            span["events"] = events
        if len(self._ring) == self._ring.maxlen:
            tracing_metrics.spans_dropped_total += 1
        self._ring.append(span)
        tracing_metrics.spans_recorded_total += 1
        return span

    def drain(self) -> List[Dict[str, Any]]:
        out = list(self._ring)
        self._ring.clear()
        return out


# The process-wide default collector every instrumentation point records to.
collector = SpanCollector()


class _SpanHandle:
    """Live span under construction: accumulate events/attrs, record on
    ``finish`` (or context-manager exit)."""

    __slots__ = ("tc", "name", "component", "t0", "attrs", "events", "_sink",
                 "parent_id", "span_id", "_done")

    def __init__(self, tc, name, component, sink, attrs=None,
                 parent_id="", span_id=None, t0=None):
        self.tc = tc
        self.name = name
        self.component = component
        self.t0 = time.perf_counter() if t0 is None else t0
        self.attrs = dict(attrs) if attrs else {}
        self.events: List[Dict[str, Any]] = []
        self._sink = sink
        self.parent_id = parent_id
        self.span_id = span_id
        self._done = False

    def set(self, **attrs) -> "_SpanHandle":
        self.attrs.update(attrs)
        return self

    def event(self, name: str, **attrs) -> None:
        ev: Dict[str, Any] = {
            "name": name,
            "t_ms": round(_wall_ms(time.perf_counter()), 3),
        }
        if attrs:
            ev.update(attrs)
        self.events.append(ev)

    def finish(self, end: Optional[float] = None) -> None:
        if self._done:
            return
        self._done = True
        self._sink.record(
            self.tc, self.name, self.component,
            self.t0, time.perf_counter() if end is None else end,
            attrs=self.attrs or None, events=self.events or None,
            span_id=self.span_id, parent_id=self.parent_id,
        )

    def __enter__(self) -> "_SpanHandle":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is not None:
            self.attrs.setdefault("error", exc_type.__name__)
        self.finish()


class _NoopSpan:
    """The unsampled fast path: every method is a no-op, one shared
    instance, zero allocation per call site."""

    __slots__ = ()

    def set(self, **attrs) -> "_NoopSpan":
        return self

    def event(self, name: str, **attrs) -> None:
        pass

    def finish(self, end: Optional[float] = None) -> None:
        pass

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        pass


NOOP_SPAN = _NoopSpan()


def span(
    tc: Optional[TraceContext],
    name: str,
    component: str,
    attrs: Optional[Dict[str, Any]] = None,
    sink: Optional[SpanCollector] = None,
    parent_id: str = "",
    t0: Optional[float] = None,
):
    """Open a span under ``tc`` (context manager or explicit ``finish``).
    Returns the shared no-op handle when the request is untraced — call
    sites stay a single ``with span(...)`` with zero cost off-trace."""
    if tc is None or not tc.sampled:
        return NOOP_SPAN
    return _SpanHandle(
        tc, name, component, sink if sink is not None else collector,
        attrs=attrs, parent_id=parent_id, t0=t0,
    )


class SeqTrace:
    """Engine-side per-sequence trace state (SequenceState.trace): the
    context plus the timing anchors the queue-wait/prefill spans need and
    the first-token latch.  Never serialized itself — the snapshot ships
    only ``ctx.to_dict()``."""

    __slots__ = ("ctx", "t_enqueue", "t_admit", "first_done")

    def __init__(self, ctx: TraceContext, t_enqueue: Optional[float] = None):
        self.ctx = ctx
        self.t_enqueue = (
            time.perf_counter() if t_enqueue is None else t_enqueue
        )
        self.t_admit: Optional[float] = None
        self.first_done = False


@dataclass
class TracingConfig:
    """The ``tracing`` config section (``DYN_TRACING__*``)."""

    enabled: bool = True
    sample: float = 0.0           # head-sampling rate [0, 1]
    ring: int = 8192              # SpanCollector capacity
    export_interval_s: float = 0.25
    ttl_s: float = 120.0          # aggregator assembly TTL
    tail_keep: bool = True        # keep edge spans for error/SLO requests
    tail_slo_ttft_ms: Optional[float] = None

    @classmethod
    def from_config(cls, section: Optional[Dict[str, Any]]) -> "TracingConfig":
        s = section or {}
        slo = s.get("tail_slo_ttft_ms")
        return cls(
            enabled=bool(s.get("enabled", True)),
            sample=max(0.0, min(1.0, float(s.get("sample", 0.0)))),
            ring=int(s.get("ring", 8192)),
            export_interval_s=float(s.get("export_interval_s", 0.25)),
            ttl_s=float(s.get("ttl_s", 120.0)),
            tail_keep=bool(s.get("tail_keep", True)),
            tail_slo_ttft_ms=float(slo) if slo is not None else None,
        )

    @classmethod
    def from_env(cls) -> "TracingConfig":
        from .config import RuntimeConfig

        try:
            return cls.from_config(RuntimeConfig.from_layers().tracing)
        except Exception:  # noqa: BLE001 — bad config must not kill serving
            logger.warning("could not load tracing config; using defaults",
                           exc_info=True)
            return cls()


class TraceSampler:
    """Edge-side sampling decision: forced (``x-trace`` header or
    ``nvext.trace``) beats the head rate; tail-keep eligibility is decided
    at request finish (llm/trace_service.EdgeRequestTrace)."""

    def __init__(self, config: Optional[TracingConfig] = None, rng=None):
        self.config = config or TracingConfig()
        self._rng = rng if rng is not None else random.random
        if self.config.ring != collector._ring.maxlen:
            collector.set_capacity(self.config.ring)

    @staticmethod
    def _forced(headers, body) -> bool:
        raw = None
        if headers is not None:
            raw = headers.get("x-trace")
        if raw is None and isinstance(body, dict):
            nvext = body.get("nvext")
            if isinstance(nvext, dict):
                raw = nvext.get("trace")
        if raw is None:
            return False
        return str(raw).lower() not in ("", "0", "false", "no", "off")

    def decide(self, headers=None, body=None) -> Optional[TraceContext]:
        """A sampled TraceContext, or None (tail-keep may still promote)."""
        if not self.config.enabled:
            return None
        if self._forced(headers, body):
            tracing_metrics.traces_forced_total += 1
            return TraceContext.new()
        if self.config.sample > 0.0 and self._rng() < self.config.sample:
            tracing_metrics.traces_sampled_total += 1
            return TraceContext.new()
        return None

    def tail_eligible(self, error: bool, ttft_ms: Optional[float]) -> bool:
        if not self.config.enabled or not self.config.tail_keep:
            return False
        if error:
            return True
        slo = self.config.tail_slo_ttft_ms
        return slo is not None and ttft_ms is not None and ttft_ms > slo


class SpanExporter:
    """Drain the collector on an interval and hand batches to ``sinks``.

    A sink is either an async callable (``await sink(payload)`` — e.g.
    ``lambda p: namespace.publish(TRACES_TOPIC, p)``) or an object with an
    (async or sync) ``ingest`` method (a colocated TraceAggregator).  A
    failed sink drops that batch for that sink only (tracing is best
    effort; it must never fail a request or wedge teardown)."""

    def __init__(
        self,
        sinks: List[Any],
        source: Optional[SpanCollector] = None,
        interval_s: float = 0.25,
        proc: Optional[str] = None,
    ):
        self.sinks = list(sinks)
        self.source = source if source is not None else collector
        self.interval_s = interval_s
        if proc:
            self.source.proc = proc
        self._task: Optional[asyncio.Task] = None

    async def start(self) -> "SpanExporter":
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(self._run())
        return self

    async def _run(self) -> None:
        try:
            while True:
                await asyncio.sleep(self.interval_s)
                await self.flush()
        except asyncio.CancelledError:
            pass

    async def _deliver(self, payload: Dict[str, Any]) -> None:
        for sink in self.sinks:
            try:
                ingest = getattr(sink, "ingest", None)
                if ingest is not None:
                    res = ingest(payload)
                else:
                    res = sink(payload)
                if asyncio.iscoroutine(res):
                    await res
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — best-effort plane
                tracing_metrics.export_errors_total += 1
                logger.warning("span batch export failed", exc_info=True)

    async def flush(self) -> int:
        """Export everything currently in the ring; returns spans shipped."""
        spans = self.source.drain()
        if not spans:
            return 0
        tracing_metrics.export_batches_total += 1
        await self._deliver({"proc": self.source.proc, "spans": spans})
        return len(spans)

    async def stop(self, final_flush: bool = True) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        if final_flush:
            await self.flush()
