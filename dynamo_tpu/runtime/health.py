"""Health watchdog: probes, straggler detection, quarantine → drain → eject.

The lease plane answers "is the process alive?"; the request-resilience
layer answers "did THIS request survive?".  Neither catches the fleet's
worst citizen: the worker that is alive enough to keep its lease but sick
enough to drag every stream routed to it (the straggler), or the worker
whose service plane is wedged while its hub connection keeps breathing.
This module closes that gap (SURVEY §5 failure detection; reference Dynamo
delegates the equivalent to etcd health + operator-level probes):

- ``probe_address``       — liveness/readiness over the EXISTING endpoint
  plane: every ``ServiceServer`` answers a built-in ``__health__`` stream
  (no new port, no new protocol), so a probe exercises the exact transport
  requests ride.
- ``WorkerLatencyTracker`` — process-global per-worker TTFT/ITL rolling
  windows, recorded by the routed client as it streams (the only vantage
  point that sees scheduling + transport + engine latency together).  The
  HTTP edge publishes the snapshot on ``slo_metrics`` so a planner-side
  watchdog can consume it cross-process.
- ``HealthWatchdog``      — periodic probe + outlier scan over the instance
  registrations; consecutive failures or a sustained ITL/TTFT outlier
  (vs the fleet median) quarantine the worker (``health/quarantine/{id}``
  in the hub KV — the planner's pool view excludes it), live sequences are
  drained via the migration plane, and after the grace window the worker's
  instance registrations are ejected so no router ever picks it again.
  A worker that recovers while quarantined (probes pass, outlier clears)
  is reinstated instead of ejected — transient GC pauses don't cost a
  healthy worker.

Everything here is host-side asyncio/stdlib; the migration drain is a lazy
import so the runtime layer stays importable without the llm stack.
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import deque
from dataclasses import dataclass, field

from ..labels import escape_label
from typing import Any, Awaitable, Callable, Dict, List, Optional, Set

logger = logging.getLogger(__name__)

# Hub KV prefix for quarantine markers (durable, NOT lease-bound: a
# quarantine decision must survive both the worker and the watchdog).
QUARANTINE_PREFIX = "health/quarantine/"


def quarantine_key(worker_id: int) -> str:
    """Quarantine marker key for one worker (shard-map routed: DYN401)."""
    from .transports.shard import hub_key  # lazy: shard imports hub only

    return hub_key("health", "quarantine", worker_id)

# Service-plane path every ServiceServer answers without registration.
HEALTH_ENDPOINT = "__health__"


def _median(xs: List[float]) -> float:
    if not xs:
        return 0.0
    xs = sorted(xs)
    return xs[len(xs) // 2]


# --------------------------------------------------------------------------
# Per-worker latency tracking (client-side vantage point)
# --------------------------------------------------------------------------


class WorkerLatencyTracker:
    """Rolling per-worker TTFT/ITL windows, fed by the routed client.

    Bounded deques per worker; ``snapshot()`` renders p50s for the
    straggler scan and for the edge's ``slo_metrics`` publication.  Workers
    that stop being observed age out via ``prune`` (called on snapshot)."""

    def __init__(self, window: int = 64, stale_after_s: float = 60.0,
                 clock: Callable[[], float] = time.monotonic):
        self.window = window
        self.stale_after_s = stale_after_s
        self._clock = clock
        self._ttft: Dict[int, deque] = {}
        self._itl: Dict[int, deque] = {}
        self._address: Dict[int, str] = {}
        self._last_seen: Dict[int, float] = {}

    def record_ttft(self, worker_id: int, address: str, ms: float) -> None:
        if worker_id is None:
            return
        self._ttft.setdefault(worker_id, deque(maxlen=self.window)).append(ms)
        self._address[worker_id] = address
        self._last_seen[worker_id] = self._clock()

    def record_itl(self, worker_id: int, address: str, ms: float) -> None:
        if worker_id is None:
            return
        self._itl.setdefault(worker_id, deque(maxlen=self.window)).append(ms)
        self._address[worker_id] = address
        self._last_seen[worker_id] = self._clock()

    def forget(self, worker_id: int) -> None:
        self._ttft.pop(worker_id, None)
        self._itl.pop(worker_id, None)
        self._address.pop(worker_id, None)
        self._last_seen.pop(worker_id, None)

    def _prune(self) -> None:
        now = self._clock()
        for wid, t in list(self._last_seen.items()):
            if now - t > self.stale_after_s:
                self.forget(wid)

    def snapshot(self) -> Dict[int, Dict[str, Any]]:
        """worker_id → {address, ttft_p50_ms, itl_p50_ms, n} for every
        worker with at least one sample in the window."""
        self._prune()
        out: Dict[int, Dict[str, Any]] = {}
        for wid in set(self._ttft) | set(self._itl):
            ttft = list(self._ttft.get(wid, ()))
            itl = list(self._itl.get(wid, ()))
            out[wid] = {
                "address": self._address.get(wid, ""),
                "ttft_p50_ms": _median(ttft) if ttft else None,
                "itl_p50_ms": _median(itl) if itl else None,
                "n": len(ttft) + len(itl),
            }
        return out

    def reset(self) -> None:
        self._ttft.clear()
        self._itl.clear()
        self._address.clear()
        self._last_seen.clear()


# Process-global tracker the routed client records into (runtime/client.py)
# and the edge publishes from (planner/signals.py EdgeSloPublisher).
worker_latency = WorkerLatencyTracker()


# --------------------------------------------------------------------------
# KV corruption ledger (the integrity plane's watchdog feed)
# --------------------------------------------------------------------------


class KvCorruptionLedger:
    """Sliding-window count of checksum-failed KV payloads per source
    worker (engine/integrity.py; docs/kv_tiering.md §integrity).

    Fed by ``inject_blocks(donor=...)`` when a pulled/transferred payload
    fails verification, and by engines whose OWN tiers detect rot (via
    ``set_integrity_reporter`` wiring).  The watchdog folds the counts
    into its scan: one flipped byte is weather, but a donor (or a local
    medium) that keeps shipping poison is a sick worker — every pull from
    it costs a detection + recompute, so it gets the same quarantine →
    drain → eject path as a prober failure.  Counts age out of the
    ``window_s`` horizon so a healed worker reinstates."""

    def __init__(self, window_s: float = 60.0,
                 clock: Callable[[], float] = time.monotonic):
        self.window_s = window_s
        self._clock = clock
        self._events: Dict[int, deque] = {}

    def record(self, worker_id: int, n: int = 1) -> None:
        if worker_id is None:
            return
        q = self._events.setdefault(worker_id, deque(maxlen=256))
        now = self._clock()
        for _ in range(n):
            q.append(now)

    def count(self, worker_id: int) -> int:
        q = self._events.get(worker_id)
        if not q:
            return 0
        horizon = self._clock() - self.window_s
        while q and q[0] < horizon:
            q.popleft()
        if not q:
            self._events.pop(worker_id, None)
            return 0
        return len(q)

    def counts(self) -> Dict[int, int]:
        return {
            wid: c for wid in list(self._events)
            if (c := self.count(wid)) > 0
        }

    def reset(self) -> None:
        self._events.clear()


# Process-global ledger: engines record into it (transfer/offload paths),
# the watchdog scans it each tick.
kv_corruption = KvCorruptionLedger()


# --------------------------------------------------------------------------
# Probing
# --------------------------------------------------------------------------


async def probe_address(address: str, timeout_s: float = 1.0) -> bool:
    """Liveness+readiness probe over the service plane's ``__health__``
    stream.  True only if the worker answered ok AND reports at least one
    registered endpoint (alive-but-empty = not ready)."""
    from .engine import Context
    from .transports.service import RemoteEngine

    if not address:
        return True  # endpoint-less registrations (prefill heartbeats)
    try:
        async def _roundtrip() -> bool:
            stream = await RemoteEngine(address, HEALTH_ENDPOINT).generate(
                Context({})
            )
            try:
                async for item in stream:
                    return bool(item.get("ok")) and int(item.get("endpoints", 0)) > 0
                return False
            finally:
                # `async for` does not aclose() on early return: without
                # this, every SUCCESSFUL probe leaked its mux stream slot
                # and a pending forward_cancel task — one per probe tick,
                # forever (caught by the suite-wide orphan-task detector).
                await stream.aclose()

        return await asyncio.wait_for(_roundtrip(), timeout_s)
    except asyncio.CancelledError:
        raise
    except Exception:  # noqa: BLE001 — any failure IS the probe result
        return False


# --------------------------------------------------------------------------
# Watchdog
# --------------------------------------------------------------------------


@dataclass
class HealthConfig:
    probe_interval_s: float = 1.0
    probe_timeout_s: float = 1.0
    # consecutive probe failures before quarantine (1 = first failure)
    quarantine_after: int = 2
    # straggler: worker p50 > factor × fleet median, sustained for
    # ``straggler_streak`` scans, with an absolute floor so microsecond
    # jitter between idle workers never reads as an outlier
    straggler_factor: float = 3.0
    straggler_min_ms: float = 50.0
    straggler_min_samples: int = 5
    straggler_streak: int = 2
    # KV-corruption quarantine bar: checksum-failed payloads attributed to
    # one worker within the ledger window (``kv_corruption``) before it is
    # quarantined — one flip is weather, a streak is a sick medium/donor
    corrupt_after: int = 3
    # quarantine → eject grace (drain budget); recovery within it reinstates
    eject_grace_s: float = 5.0
    # eject = delete the worker's instance registrations (permanent until
    # the process re-registers); False = quarantine+drain only
    eject: bool = True

    @classmethod
    def from_config(cls, cfg: Optional[Dict[str, Any]]) -> "HealthConfig":
        cfg = cfg or {}
        kw = {}
        for f in (
            "probe_interval_s", "probe_timeout_s", "straggler_factor",
            "straggler_min_ms", "eject_grace_s",
        ):
            if cfg.get(f) is not None:
                kw[f] = float(cfg[f])
        for f in ("quarantine_after", "straggler_min_samples",
                  "straggler_streak", "corrupt_after"):
            if cfg.get(f) is not None:
                kw[f] = int(cfg[f])
        if cfg.get("eject") is not None:
            kw["eject"] = bool(cfg["eject"])
        return cls(**kw)


@dataclass
class WorkerHealth:
    """Watchdog-side record for one discovered worker."""

    worker_id: int
    address: str = ""
    keys: Set[str] = field(default_factory=set)
    info: Optional[Dict[str, Any]] = None  # last instance record w/ metadata
    state: str = "healthy"  # healthy | quarantined | ejected
    fail_streak: int = 0
    straggler_streak: int = 0
    quarantined_at: float = 0.0
    reason: str = ""


class HealthMetrics:
    """Process-global watchdog counters (appended to /metrics)."""

    def __init__(self):
        self.probes_total = 0
        self.probe_failures_total = 0
        self.stragglers_detected_total = 0
        self.quarantines_total = 0
        self.recoveries_total = 0
        self.drains_total = 0
        self.drained_sequences_total = 0
        self.ejections_total = 0
        self.corruption_quarantines_total = 0
        self.state_counts: Dict[str, int] = {}

    def reset(self) -> None:
        self.__init__()

    def render(self, prefix: str = "dynamo_tpu") -> str:
        ns = f"{prefix}_health"
        lines = []

        def counter(name: str, help_: str, value: int) -> None:
            lines.append(f"# HELP {ns}_{name} {help_}")
            lines.append(f"# TYPE {ns}_{name} counter")
            lines.append(f"{ns}_{name} {value}")

        counter("probes_total", "Worker liveness probes sent", self.probes_total)
        counter("probe_failures_total", "Failed worker probes",
                self.probe_failures_total)
        counter("stragglers_detected_total",
                "ITL/TTFT outlier detections", self.stragglers_detected_total)
        counter("quarantines_total", "Workers quarantined",
                self.quarantines_total)
        counter("recoveries_total", "Quarantined workers reinstated",
                self.recoveries_total)
        counter("drains_total", "Quarantine drains attempted", self.drains_total)
        counter("drained_sequences_total",
                "Sequences migrated off quarantined workers",
                self.drained_sequences_total)
        counter("ejections_total", "Workers ejected from the fleet",
                self.ejections_total)
        counter("corruption_quarantines_total",
                "Quarantines attributed to repeated KV corruption",
                self.corruption_quarantines_total)
        lines.append(f"# HELP {ns}_workers Worker count by health state")
        lines.append(f"# TYPE {ns}_workers gauge")
        for state in ("healthy", "quarantined", "ejected"):
            lines.append(
                f'{ns}_workers{{state="{escape_label(state)}"}} '
                f"{self.state_counts.get(state, 0)}"
            )
        return "\n".join(lines) + "\n"


health_metrics = HealthMetrics()


class HealthWatchdog:
    """Periodic fleet health scan over one instance prefix.

    Each ``tick``: read the instance registrations, probe every distinct
    worker address, merge the latency tracker's outlier view, advance the
    per-worker state machine, and act:

    quarantine  — write ``health/quarantine/{worker_id}`` (the planner's
                  SignalCollector watches this prefix and drops the worker
                  from its pool view) and kick off drain-via-migration for
                  its live sequences (remote ``migrate_out``, targets
                  exclude quarantined peers).
    reinstate   — probes pass and the outlier cleared before the grace
                  window ended: delete the marker, reset streaks.
    eject       — grace expired and the worker is still sick: delete its
                  instance registrations (watchers see the delete; routing
                  stops) and stamp the marker ``ejected``.

    ``prober``/``drainer``/``latency_source``/``clock`` are injectable for
    deterministic tests and for cross-process wiring (a planner-side
    watchdog feeds ``latency_source`` from the collector's slo_metrics
    view instead of the in-process tracker)."""

    def __init__(
        self,
        hub,
        instance_prefix: str,
        config: Optional[HealthConfig] = None,
        prober: Optional[Callable[[str, float], Awaitable[bool]]] = None,
        drainer: Optional[Callable[[Dict[str, Any]], Awaitable[int]]] = None,
        latency_source: Optional[Callable[[], Dict[int, Dict[str, Any]]]] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.hub = hub
        self.instance_prefix = instance_prefix
        self.config = config or HealthConfig()
        self._prober = prober or probe_address
        self._drainer = drainer or self._drain_via_migration
        self._latency_source = latency_source or worker_latency.snapshot
        self._clock = clock
        self.workers: Dict[int, WorkerHealth] = {}
        self._task: Optional[asyncio.Task] = None

    # -- lifecycle -----------------------------------------------------------

    async def start(self) -> "HealthWatchdog":
        self._task = asyncio.get_running_loop().create_task(self._run())
        return self

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _run(self) -> None:
        while True:
            try:
                await self.tick()
            except asyncio.CancelledError:
                return
            except Exception:  # noqa: BLE001 — the watchdog must outlive hubs
                logger.exception("health watchdog tick failed")
            try:
                await asyncio.sleep(self.config.probe_interval_s)
            except asyncio.CancelledError:
                return

    # -- one scan ------------------------------------------------------------

    async def tick(self) -> None:
        cfg = self.config
        try:
            snapshot = await self.hub.kv_get_prefix(self.instance_prefix)
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — hub down: skip the scan, not die
            logger.warning("health scan: hub unreachable; skipping tick")
            return
        # Fold registrations into per-worker records.
        seen: Set[int] = set()
        for key, info in snapshot.items():
            if not isinstance(info, dict) or "worker_id" not in info:
                continue
            wid = info["worker_id"]
            seen.add(wid)
            rec = self.workers.get(wid)
            if rec is None:
                rec = self.workers[wid] = WorkerHealth(worker_id=wid)
            if rec.state == "ejected":
                # Re-registration after eject = operator brought it back:
                # start over with a clean slate.
                rec.state = "healthy"
                rec.fail_streak = rec.straggler_streak = 0
                await self._clear_marker(wid)
            rec.keys.add(key)
            rec.keys &= set(snapshot.keys())
            if info.get("address"):
                rec.address = info["address"]
                rec.info = info
        for wid in list(self.workers):
            if wid not in seen and self.workers[wid].state not in (
                "quarantined", "ejected"
            ):
                # Gone from discovery (lease expiry / clean stop): forget.
                # Quarantined AND ejected records are kept — an ejected
                # worker that re-registers later (operator intervention,
                # lease-monitor re-put after a hub restart) must hit the
                # clean-slate branch above so its durable quarantine marker
                # is cleared; forgetting it would leave the marker excluding
                # a serving worker from the planner pool view forever.
                del self.workers[wid]
        # Probe every live worker address concurrently.
        probed = [
            rec for rec in self.workers.values()
            if rec.state != "ejected" and rec.address
        ]
        results = await asyncio.gather(
            *(self._prober(rec.address, cfg.probe_timeout_s) for rec in probed),
            return_exceptions=True,
        )
        for rec, ok in zip(probed, results):
            health_metrics.probes_total += 1
            if ok is True:
                rec.fail_streak = 0
            else:
                rec.fail_streak += 1
                health_metrics.probe_failures_total += 1
        # Straggler scan: each worker's p50 vs the fleet median.
        self._scan_stragglers()
        # KV-corruption ledger scan (engine/integrity.py feeds it through
        # inject_blocks donor attribution + local-tier reporters): repeated
        # checksum failures attributed to one worker inside the ledger
        # window quarantine it like a probe-failure streak would.
        corrupt_counts = kv_corruption.counts()
        # State transitions + actions.
        now = self._clock()
        for rec in list(self.workers.values()):
            poisoning = (
                corrupt_counts.get(rec.worker_id, 0) >= cfg.corrupt_after
            )
            if rec.state == "healthy":
                sick = rec.fail_streak >= cfg.quarantine_after
                slow = rec.straggler_streak >= cfg.straggler_streak
                if sick or slow or poisoning:
                    if sick:
                        rec.reason = f"probe_failures={rec.fail_streak}"
                    elif slow:
                        rec.reason = "latency_outlier"
                    else:
                        rec.reason = (
                            f"kv_corruption={corrupt_counts[rec.worker_id]}"
                        )
                        health_metrics.corruption_quarantines_total += 1
                        from ..llm.metrics import kv_integrity_metrics

                        kv_integrity_metrics.quarantined_total += 1
                    await self._quarantine(rec, now)
            elif rec.state == "quarantined":
                recovered = (
                    rec.fail_streak == 0
                    and rec.straggler_streak == 0
                    and not poisoning  # ledger entries age out of the window
                )
                if recovered:
                    await self._reinstate(rec)
                elif cfg.eject and now - rec.quarantined_at >= cfg.eject_grace_s:
                    await self._eject(rec)
        health_metrics.state_counts = {}
        for rec in self.workers.values():
            health_metrics.state_counts[rec.state] = (
                health_metrics.state_counts.get(rec.state, 0) + 1
            )

    def _scan_stragglers(self) -> None:
        cfg = self.config
        try:
            lat = self._latency_source() or {}
        except Exception:  # noqa: BLE001 — latency feed is best-effort
            return
        flagged: Set[int] = set()
        for metric in ("itl_p50_ms", "ttft_p50_ms"):
            vals = {
                wid: v[metric]
                for wid, v in lat.items()
                if isinstance(v.get(metric), (int, float))
                and v.get("n", 0) >= cfg.straggler_min_samples
            }
            if len(vals) < 2:
                continue  # nothing to be an outlier AGAINST
            fleet = _median(list(vals.values()))
            bar = max(fleet * cfg.straggler_factor, cfg.straggler_min_ms)
            for wid, v in vals.items():
                if v > bar and wid not in flagged:
                    flagged.add(wid)
                    rec = self.workers.get(wid)
                    if rec is None or rec.state == "ejected":
                        continue
                    rec.straggler_streak += 1
                    health_metrics.stragglers_detected_total += 1
                    logger.warning(
                        "straggler: worker %s %s=%.1fms vs fleet median "
                        "%.1fms (streak %d)",
                        wid, metric, v, fleet, rec.straggler_streak,
                    )
        # An outlier that cleared resets its streak — quarantine needs a
        # SUSTAINED signal, not two isolated blips a minute apart.
        for wid, rec in self.workers.items():
            if wid not in flagged and rec.straggler_streak:
                rec.straggler_streak = 0

    # -- actions -------------------------------------------------------------

    async def _quarantine(self, rec: WorkerHealth, now: float) -> None:
        rec.state = "quarantined"
        rec.quarantined_at = now
        health_metrics.quarantines_total += 1
        logger.warning(
            "quarantining worker %s (%s): %s",
            rec.worker_id, rec.address, rec.reason,
        )
        try:
            await self.hub.kv_put(
                quarantine_key(rec.worker_id),
                {"state": "quarantined", "reason": rec.reason,
                 "address": rec.address},
            )
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — marker is advisory; drain anyway
            logger.warning("could not write quarantine marker", exc_info=True)
        if rec.info is not None:
            health_metrics.drains_total += 1
            try:
                moved = await self._drainer(rec.info)
                health_metrics.drained_sequences_total += int(moved or 0)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — a stuck drain must not wedge
                logger.warning(
                    "drain of quarantined worker %s failed", rec.worker_id,
                    exc_info=True,
                )

    async def _drain_via_migration(self, info: Dict[str, Any]) -> int:
        """Default drainer: remote ``migrate_out`` of every live sequence to
        a non-quarantined migration-capable peer."""
        from ..llm.migration.coordinator import (  # lazy: llm imports runtime
            pick_migration_target,
            request_migrate_out,
        )

        quarantined = frozenset(
            wid for wid, r in self.workers.items() if r.state != "healthy"
        )
        target = await pick_migration_target(
            self.hub,
            self.instance_prefix,
            info.get("worker_id"),
            exclude=quarantined,
        )
        if target is None:
            logger.info("quarantine drain: no migration-capable peer")
            return 0
        resp = await request_migrate_out(info, target)
        return len(resp.get("migrated") or ())

    async def _reinstate(self, rec: WorkerHealth) -> None:
        rec.state = "healthy"
        rec.quarantined_at = 0.0
        health_metrics.recoveries_total += 1
        logger.info("worker %s recovered; reinstating", rec.worker_id)
        await self._clear_marker(rec.worker_id)

    async def _eject(self, rec: WorkerHealth) -> None:
        rec.state = "ejected"
        health_metrics.ejections_total += 1
        logger.warning(
            "ejecting worker %s (%s) after quarantine grace",
            rec.worker_id, rec.address,
        )
        for key in sorted(rec.keys):
            try:
                await self.hub.kv_delete(key)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — keep deleting the rest
                logger.warning("eject: delete %s failed", key, exc_info=True)
        try:
            await self.hub.kv_put(
                quarantine_key(rec.worker_id),
                {"state": "ejected", "reason": rec.reason,
                 "address": rec.address},
            )
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001
            pass

    async def _clear_marker(self, worker_id: int) -> None:
        try:
            await self.hub.kv_delete(quarantine_key(worker_id))
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001
            pass
