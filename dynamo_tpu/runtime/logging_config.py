"""Logging subsystem: DYN_LOG level filters + JSONL structured output.

Reference semantics: lib/runtime/src/logging.rs:16-100 — ``DYN_LOG`` is an
env-filter string ("info", "warn,dynamo_tpu.engine=debug", ...) selecting a
default level plus per-module overrides; ``DYN_LOG_FORMAT=jsonl`` switches to
one JSON object per line (time/level/target/message + extra fields), the
shape their log pipeline ships to collectors.  ``DYN_LOG_FILE`` tees to a
file.  ``setup_logging()`` is idempotent and called by every CLI entrypoint.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time
from typing import Dict, Optional, Tuple

_LEVELS = {
    "trace": logging.DEBUG,  # python has no TRACE; map down
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "error": logging.ERROR,
}


def parse_filter(spec: str) -> Tuple[int, Dict[str, int]]:
    """"warn,dynamo_tpu.engine=debug" → (WARNING, {module: DEBUG})."""
    default = logging.INFO
    per_module: Dict[str, int] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        if "=" in part:
            mod, _, lvl = part.partition("=")
            per_module[mod.strip()] = _LEVELS.get(lvl.strip().lower(), logging.INFO)
        else:
            default = _LEVELS.get(part.lower(), logging.INFO)
    return default, per_module


class JsonlFormatter(logging.Formatter):
    """One JSON object per line (reference logging.rs JSONL shape)."""

    def format(self, record: logging.LogRecord) -> str:
        out = {
            "time": time.strftime(
                "%Y-%m-%dT%H:%M:%S", time.gmtime(record.created)
            )
            + f".{int(record.msecs):03d}Z",
            "level": record.levelname,
            "target": record.name,
            "message": record.getMessage(),
        }
        if record.exc_info:
            out["exception"] = self.formatException(record.exc_info)
        for key, val in getattr(record, "fields", {}).items():
            out.setdefault(key, val)
        return json.dumps(out, separators=(",", ":"))


def setup_logging(
    spec: Optional[str] = None,
    fmt: Optional[str] = None,
    log_file: Optional[str] = None,
) -> None:
    """Install handlers per DYN_LOG / DYN_LOG_FORMAT / DYN_LOG_FILE.

    Idempotent: replaces handlers this module installed, leaves foreign
    handlers (pytest's caplog etc.) alone.
    """
    spec = spec if spec is not None else os.environ.get("DYN_LOG", "info")
    fmt = fmt if fmt is not None else os.environ.get("DYN_LOG_FORMAT", "text")
    log_file = (
        log_file if log_file is not None else os.environ.get("DYN_LOG_FILE")
    )
    default, per_module = parse_filter(spec)

    root = logging.getLogger()
    root.setLevel(default)
    for mod, lvl in per_module.items():
        logging.getLogger(mod).setLevel(lvl)

    if fmt.lower() in ("jsonl", "json"):
        formatter: logging.Formatter = JsonlFormatter()
    else:
        formatter = logging.Formatter(
            "%(asctime)s %(levelname).1s %(name)s: %(message)s",
            datefmt="%H:%M:%S",
        )
    for h in list(root.handlers):
        if getattr(h, "_dyn_installed", False):
            root.removeHandler(h)
    handler: logging.Handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(formatter)
    handler._dyn_installed = True  # type: ignore[attr-defined]
    root.addHandler(handler)
    if log_file:
        fh = logging.FileHandler(log_file)
        fh.setFormatter(JsonlFormatter())  # files always structured
        fh._dyn_installed = True  # type: ignore[attr-defined]
        root.addHandler(fh)
